"""Non-blocking checkpoints (--ckpt-async, checkpoint.AsyncSaver):
ordering/error semantics of the background writer, byte/bit identity of
async vs sync saves for both formats, the driver-level flow, and the
crash-safety guarantee — a kill mid-background-write leaves the previous
bestmodel loadable (subprocess harness in tests/_ckpt_child.py)."""

import os
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from tests._subproc import child_env

from distributedpytorch_tpu import checkpoint as ckpt
from distributedpytorch_tpu import telemetry
from distributedpytorch_tpu.models import get_model
from distributedpytorch_tpu.ops.losses import get_loss_fn
from distributedpytorch_tpu.train.engine import Engine, make_optimizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def restore_global():
    yield
    telemetry._active = telemetry.Telemetry(enabled=False)


def _engine():
    model = get_model("mlp", 10, half_precision=False)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, 4, False)
    return Engine(model, "mlp", get_loss_fn("cross_entropy"), tx,
                  mean=0.45, std=0.2, input_size=28,
                  half_precision=False)


@pytest.fixture(scope="module")
def trained_state():
    """One real optimizer step so opt_state moments are non-trivial."""
    engine = _engine()
    state = engine.init_state(jax.random.PRNGKey(7))
    rng = np.random.default_rng(0)
    state, _ = engine.train_step(
        state, rng.integers(0, 256, (8, 28, 28), np.uint8),
        rng.integers(0, 10, (8,)).astype(np.int32), np.ones(8, bool),
        jax.random.PRNGKey(1))
    return engine, state


# -- AsyncSaver semantics ----------------------------------------------


def test_saver_runs_jobs_in_order_and_waits():
    saver = ckpt.AsyncSaver()
    order = []
    gate = threading.Event()

    def slow():
        gate.wait(5)
        order.append("a")

    saver.submit(slow)
    saver.submit(lambda: order.append("b"))
    assert saver.in_flight
    assert order == []  # both queued behind the gate — nothing blocked
    gate.set()
    saver.wait()
    assert order == ["a", "b"]
    assert not saver.in_flight
    saver.close()


def test_saver_background_error_reraises_on_driver_thread():
    saver = ckpt.AsyncSaver()

    def boom():
        raise RuntimeError("disk gone")

    saver.submit(boom)
    with pytest.raises(RuntimeError, match="disk gone"):
        saver.wait()
    # the saver recovers: later jobs still run
    done = []
    saver.submit(lambda: done.append(1))
    saver.close()
    assert done == [1]


def test_saver_close_retires_worker_thread():
    before = set(threading.enumerate())
    saver = ckpt.AsyncSaver()
    saver.submit(lambda: None)
    saver.close()
    deadline = time.monotonic() + 5
    while set(threading.enumerate()) - before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert set(threading.enumerate()) == before


# -- async == sync equivalence (both formats) ---------------------------


def test_msgpack_async_file_is_byte_identical_to_sync(tmp_path,
                                                      trained_state):
    _, state = trained_state
    sync_path = str(tmp_path / "sync.ckpt")
    async_path = str(tmp_path / "async.ckpt")
    ckpt.save_checkpoint(sync_path, "mlp", state, 3, 0.25)
    saver = ckpt.AsyncSaver()
    ckpt.save_checkpoint_async(saver, async_path, "mlp", state, 3, 0.25)
    saver.close()
    with open(sync_path, "rb") as f:
        sync_bytes = f.read()
    with open(async_path, "rb") as f:
        async_bytes = f.read()
    assert sync_bytes == async_bytes  # resume is trivially bit-identical


def test_msgpack_async_resume_state_bit_identical(tmp_path, trained_state):
    engine, state = trained_state
    path = str(tmp_path / "async.ckpt")
    saver = ckpt.AsyncSaver()
    ckpt.save_checkpoint_async(saver, path, "mlp", state, 3, 0.25)
    saver.close()
    template = engine.init_state(jax.random.PRNGKey(2))
    restored, next_epoch, best = ckpt.load_checkpoint(path, template)
    assert next_epoch == 4 and best == 0.25
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(state)),
                    jax.tree_util.tree_leaves(jax.device_get(restored))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_orbax_async_restore_bit_identical_to_sync(tmp_path,
                                                   trained_state):
    engine, state = trained_state
    sync_path = str(tmp_path / "sync_ck")
    async_path = str(tmp_path / "async_ck")
    ckpt.save_checkpoint(sync_path, "mlp", state, 3, 0.25, fmt="orbax")
    saver = ckpt.AsyncSaver()
    ckpt.save_checkpoint_async(saver, async_path, "mlp", state, 3, 0.25,
                               fmt="orbax")
    saver.close()
    assert os.path.isdir(async_path)
    assert not os.path.exists(async_path + ".tmp")  # finalize swapped it

    restored = {}
    for name, path in (("sync", sync_path), ("async", async_path)):
        template = engine.init_state(jax.random.PRNGKey(2))
        restored[name], next_epoch, best = ckpt.load_checkpoint(path,
                                                                template)
        assert next_epoch == 4 and best == 0.25
    leaves = zip(jax.tree_util.tree_leaves(jax.device_get(state)),
                 jax.tree_util.tree_leaves(
                     jax.device_get(restored["sync"])),
                 jax.tree_util.tree_leaves(
                     jax.device_get(restored["async"])))
    for orig, s, a in leaves:
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(s))
        np.testing.assert_array_equal(np.asarray(orig), np.asarray(a))


# -- telemetry split ----------------------------------------------------


def test_async_save_splits_blocking_from_background_span(tmp_path,
                                                         trained_state,
                                                         restore_global,
                                                         monkeypatch):
    """--ckpt-async removes the write from the critical path: with an
    artificially slow background write, the blocking span stays tiny
    while the background span carries the full write duration."""
    _, state = trained_state
    tel = telemetry.configure(str(tmp_path), enabled=True, rank=0)

    orig = ckpt._write_msgpack

    def slow_write(path, payload):
        time.sleep(0.5)
        orig(path, payload)

    monkeypatch.setattr(ckpt, "_write_msgpack", slow_write)
    saver = ckpt.AsyncSaver()
    t0 = time.perf_counter()
    ckpt.save_checkpoint_async(saver, str(tmp_path / "ck"), "mlp", state,
                               0, 1.0)
    submit_s = time.perf_counter() - t0
    assert submit_s < 0.4  # the 0.5 s write did not block the driver
    saver.close()
    tel.close()

    import json
    events = [json.loads(line)
              for line in open(tmp_path / "telemetry" / "rank0.jsonl")]
    spans = {e["name"]: e for e in events if e["kind"] == "span"}
    assert spans["ckpt_save_background"]["dur_s"] >= 0.5
    assert spans["ckpt_save_blocking"]["dur_s"] \
        < spans["ckpt_save_background"]["dur_s"] / 2
    # the background span was emitted from the writer thread with no
    # parent leakage from the driver's stack
    assert spans["ckpt_save_background"]["parent"] is None


# -- crash safety (subprocess harness) ----------------------------------


@pytest.mark.parametrize("fmt", ["msgpack", "orbax"])
def test_kill_mid_background_write_keeps_previous_bestmodel(tmp_path, fmt):
    """A process dying while the background writer is mid-write must
    leave the previously saved bestmodel fully loadable (tmp->rename:
    the final path is only ever touched by a completed write)."""
    rsl = str(tmp_path / "rsl")
    os.makedirs(rsl)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "_ckpt_child.py"),
         "--rsl", rsl, "--ckpt-format", fmt, "--async-crash",
         "--devices-per-proc", "1"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env=child_env())
    assert r.returncode == 0, r.stderr[-3000:]
    assert "dying mid-background-write" in r.stderr

    best = ckpt.best_model_path(rsl, "synthetic", "mlp")
    # v1 (epoch 1, loss 0.5) is intact; the half-written v2 never landed
    assert ckpt.get_checkpoint_model_name(best) == "mlp"
    engine = _engine()
    template = engine.init_state(jax.random.PRNGKey(3))
    _, next_epoch, best_loss = ckpt.load_checkpoint(best, template)
    assert next_epoch == 2 and best_loss == 0.5


# -- driver-level flow --------------------------------------------------


def test_run_train_ckpt_async_resume_matches_sync(tmp_path,
                                                  restore_global):
    """Same config trained with sync vs async checkpointing produces
    byte-identical rolling + best files, and the async run's checkpoint
    resumes cleanly."""
    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    files = {}
    for mode, async_flag in (("sync", False), ("async", True)):
        rsl = str(tmp_path / mode)
        cfg = Config(action="train", data_path="/tmp/nodata", rsl_path=rsl,
                     dataset="synthetic", model_name="mlp", batch_size=8,
                     nb_epochs=1, debug=True, half_precision=False,
                     ckpt_async=async_flag)
        run_train(cfg)
        path = ckpt.checkpoint_path(rsl, "synthetic", "mlp", 0)
        assert os.path.exists(path)
        with open(path, "rb") as f:
            files[mode] = f.read()
    assert files["sync"] == files["async"]
