"""The pure SLO evaluator (distributedpytorch_tpu/slo.py, ISSUE 16).

Everything here runs on hand-built sample windows — no sockets, no
processes, and no clocks: the evaluator's only notion of time is the
``t`` each sample carries, which is exactly what lets the fleet
simulator and the autoscaler consume it unchanged.  Burn-rate window
math (fast burn fires, slow burn holds, recovery clears), windowed
quantiles from delta sketches, one-line spec validation, determinism,
and graftlint rule 13 staying clean on the module itself.
"""

import json
import os

import pytest

from distributedpytorch_tpu import slo, telemetry

# -- helpers -----------------------------------------------------------

ERROR_SLO = {
    "name": "serve-errors", "kind": "ratio",
    "bad": "dpt_serve_failed_total",
    "total": "dpt_serve_requests_total",
    "target": 0.99,
    # fast window: 10s at 2x burn; slow window: 60s at 1x — both must
    # exceed for the objective to fire (multi-window burn rate).
    "windows": [{"seconds": 10, "burn": 2.0},
                {"seconds": 60, "burn": 1.0}],
}


def _sample(t, bad=0.0, total=0.0, extra=None, hists=None):
    counters = {"dpt_serve_failed_total": bad,
                "dpt_serve_requests_total": total}
    counters.update(extra or {})
    return {"t": float(t), "counters": counters,
            "histograms": hists or {}}


def _hist_state(values):
    h = telemetry.Histogram("x")
    for v in values:
        h.observe(v)
    return {"count": h.count, "sum": h.sum, "min": h.min, "max": h.max,
            "nonpos": h._nonpos, "buckets": dict(h._buckets)}


# -- spec validation ---------------------------------------------------

def test_validate_spec_accepts_the_worked_example():
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    assert slos[0]["name"] == "serve-errors"


@pytest.mark.parametrize("mutate,expect", [
    (lambda s: s.pop("name"), "name"),
    (lambda s: s.update(name="bad name!"), "A-Za-z0-9"),
    (lambda s: s.update(kind="nope"), "kind"),
    (lambda s: s.update(windows=[]), "windows"),
    (lambda s: s.update(windows=[{"seconds": -1}]), "seconds"),
    (lambda s: s.update(windows=[{"seconds": 5}]), "burn"),
    (lambda s: s.pop("bad"), "'bad'"),
    (lambda s: s.update(target=1.5), "target"),
])
def test_validate_spec_errors_are_one_actionable_line(mutate, expect):
    spec = json.loads(json.dumps(ERROR_SLO))
    mutate(spec)
    with pytest.raises(ValueError) as e:
        slo.validate_spec({"slos": [spec]})
    msg = str(e.value)
    assert expect in msg and "\n" not in msg
    assert "serve-errors" in msg or "slos[0]" in msg


def test_validate_spec_rejects_duplicates_and_empty():
    with pytest.raises(ValueError, match="duplicate"):
        slo.validate_spec({"slos": [ERROR_SLO, ERROR_SLO]})
    with pytest.raises(ValueError, match="empty"):
        slo.validate_spec({"slos": []})
    with pytest.raises(ValueError, match="'slos'"):
        slo.validate_spec(["not", "an", "object"])


def test_load_spec_names_the_file(tmp_path):
    p = tmp_path / "slo.json"
    p.write_text("{ not json")
    with pytest.raises(ValueError, match="slo.json"):
        slo.load_spec(str(p))
    with pytest.raises(ValueError, match="cannot read"):
        slo.load_spec(str(tmp_path / "absent.json"))
    p.write_text(json.dumps({"slos": [ERROR_SLO]}))
    assert slo.load_spec(str(p))[0]["kind"] == "ratio"


# -- burn-rate window math ---------------------------------------------

def test_fast_burn_fires():
    """A sustained 10% error rate against a 99% target burns at 10x:
    both windows exceed and the objective fires."""
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    samples = [_sample(t, bad=10.0 * i, total=100.0 * i)
               for i, t in enumerate(range(0, 70, 5))]
    (v,) = slo.evaluate(slos, samples)
    assert v["firing"]
    assert all(w["exceeded"] for w in v["windows"])
    assert v["windows"][0]["value"] == pytest.approx(10.0)


def test_slow_burn_holds():
    """An old error burst outside the fast window must NOT fire: the
    long window still remembers it, the short window has recovered —
    the multi-window AND is what stops the stale page."""
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    samples = [_sample(0, bad=0, total=0),
               _sample(5, bad=30, total=100),    # the burst
               _sample(30, bad=30, total=500),
               _sample(55, bad=30, total=900),
               _sample(60, bad=30, total=1000)]  # clean since t=5
    (v,) = slo.evaluate(slos, samples)
    assert not v["firing"]
    fast, slow = v["windows"]
    assert slow["exceeded"] and not fast["exceeded"]


def test_recovery_clears():
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    burning = [_sample(t, bad=5.0 * i, total=50.0 * i)
               for i, t in enumerate(range(0, 70, 5))]
    assert slo.evaluate(slos, burning)[0]["firing"]
    # 120 clean seconds later both windows see zero new errors
    last = burning[-1]
    bad = last["counters"]["dpt_serve_failed_total"]
    tot = last["counters"]["dpt_serve_requests_total"]
    recovered = burning + [
        _sample(last["t"] + dt, bad=bad, total=tot + 10.0 * dt)
        for dt in range(5, 125, 5)]
    assert not slo.evaluate(slos, recovered)[0]["firing"]


def test_no_traffic_and_short_series_do_not_fire():
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    assert not slo.evaluate(slos, [])[0]["firing"]
    assert not slo.evaluate(slos, [_sample(0, 5, 10)])[0]["firing"]
    idle = [_sample(t, bad=7.0, total=7.0) for t in range(0, 70, 5)]
    assert not slo.evaluate(slos, idle)[0]["firing"]  # no deltas


def test_determinism_same_window_same_verdicts():
    slos = slo.validate_spec({"slos": [ERROR_SLO]})
    samples = [_sample(t, bad=2.0 * i, total=40.0 * i)
               for i, t in enumerate(range(0, 70, 5))]
    a = slo.evaluate(slos, samples)
    b = slo.evaluate(slos, json.loads(json.dumps(samples)))
    assert a == b


# -- quantile + share objectives ---------------------------------------

def test_quantile_objective_uses_windowed_delta_sketch():
    spec = {"slos": [{"name": "p95", "kind": "quantile",
                      "series": "dpt_serve_request_latency_ms",
                      "q": 0.95, "max": 100.0,
                      "windows": [{"seconds": 10}]}]}
    slos = slo.validate_spec(spec)
    slow_then_fast = [
        _sample(0, hists={"dpt_serve_request_latency_ms":
                          _hist_state([500.0] * 100)}),
        _sample(20, hists={"dpt_serve_request_latency_ms":
                           _hist_state([500.0] * 100 + [10.0] * 100)}),
    ]
    (v,) = slo.evaluate(slos, slow_then_fast)
    # lifetime p95 is ~500ms, but the WINDOW only saw the 10ms tail:
    # the startup spike must not page forever
    assert not v["firing"]
    assert v["windows"][0]["value"] == pytest.approx(10.0, rel=0.05)
    fast_then_slow = [
        _sample(0, hists={"dpt_serve_request_latency_ms":
                          _hist_state([10.0] * 100)}),
        _sample(20, hists={"dpt_serve_request_latency_ms":
                           _hist_state([10.0] * 100 + [500.0] * 100)}),
    ]
    (v2,) = slo.evaluate(slos, fast_then_slow)
    assert v2["firing"]
    assert v2["windows"][0]["value"] == pytest.approx(500.0, rel=0.05)


def test_share_objective_over_goodput_categories():
    spec = {"slos": [{"name": "compute-share", "kind": "share",
                      "category": "compute", "min": 0.5,
                      "windows": [{"seconds": 30}]}]}
    slos = slo.validate_spec(spec)

    def gp(compute, other):
        return {'dpt_goodput_seconds_total{category="compute"}': compute,
                'dpt_goodput_seconds_total{category="input_wait"}': other}

    healthy = [_sample(0, extra=gp(0, 0)), _sample(35, extra=gp(30, 5))]
    (v,) = slo.evaluate(slos, healthy)
    assert not v["firing"]
    starved = [_sample(0, extra=gp(0, 0)), _sample(35, extra=gp(5, 30))]
    (v2,) = slo.evaluate(slos, starved)
    assert v2["firing"]
    assert v2["windows"][0]["value"] == pytest.approx(5 / 35, rel=0.01)


# -- incidents report --------------------------------------------------

def test_incidents_report_empty_and_with_bundles(tmp_path):
    text = slo.incidents_report(str(tmp_path))
    assert "no incidents" in text
    bundle = {"kind": "incident", "slo": "serve-errors",
              "slo_kind": "ratio", "cycle": 7,
              "windows": [{"seconds": 10, "value": 12.0,
                           "threshold": 2.0, "t_start": 1.0,
                           "t_end": 11.0}],
              "suspect_ranks": [1],
              "offending_requests": ["r1-000004", "r1-000005"],
              "healthz": {"0": {"status": "ok"}, "1": None}}
    (tmp_path / "incident-001-serve-errors.json").write_text(
        json.dumps(bundle))
    text = slo.incidents_report(str(tmp_path))
    assert "serve-errors" in text and "r1-000004" in text
    assert "suspect ranks: [1]" in text
    assert "(down)" in text  # rank 1's healthz was unreachable
    assert len(slo.load_incidents(str(tmp_path))) == 1


# -- purity is enforced, not aspirational ------------------------------

def test_slo_module_is_clock_free_under_graftlint_rule_13():
    from distributedpytorch_tpu.analysis.core import lint_paths

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "distributedpytorch_tpu", "slo.py")
    findings, _ = lint_paths([path], root=repo)
    clock = [f for f in findings
             if f.rule == "wall-clock-in-measurement"]
    assert clock == []
    # stronger than the lint rule: the module never imports time at all
    with open(path, encoding="utf-8") as f:
        src = f.read()
    assert "import time" not in src
