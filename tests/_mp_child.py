"""Child process for the multi-process distributed test (test_multiprocess.py).

Run as a plain ``python tests/_mp_child.py`` subprocess — one per simulated
host.  Each child provisions its own local virtual CPU devices, joins the
gloo rendezvous via ``runtime.initialize_distributed`` (the TPU-native
equivalent of the reference's per-node ``init_process_group``, ref
classif.py:86-87 + main.py:128-135), runs one epoch of ``run_train`` on both
the device-resident and the streaming data path, and dumps its local copy of
the final parameters for the parent to compare across ranks.
"""

import argparse
import json
import os
import sys


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coord", required=True)
    ap.add_argument("--nproc", type=int, required=True)
    ap.add_argument("--pid", type=int, required=True)
    ap.add_argument("--devices-per-proc", type=int, default=2)
    ap.add_argument("--rsl", required=True)
    ap.add_argument("--out", required=True)
    a = ap.parse_args()

    # Local device fan-out + platform must be pinned before any backend
    # init; the rendezvous must happen before that too.
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={a.devices_per_proc}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)

    from distributedpytorch_tpu import runtime

    runtime.initialize_distributed(coordinator_address=a.coord,
                                   num_processes=a.nproc, process_id=a.pid)
    assert jax.process_count() == a.nproc, jax.process_count()
    assert jax.device_count() == a.nproc * a.devices_per_proc
    assert runtime.is_main() == (a.pid == 0)

    import numpy as np

    from distributedpytorch_tpu.cli import run_train
    from distributedpytorch_tpu.config import Config

    def local_copy(tree):
        # Replicated jax.Arrays: every process holds full copies on its own
        # devices — read this process's copy without a cross-host gather.
        # gather_replicated output may already be host numpy arrays.
        return [np.asarray(leaf.addressable_shards[0].data)
                if hasattr(leaf, "addressable_shards") else np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(tree)]

    out = {}
    # Device-resident path: epoch_plan / epoch_plan_many go through
    # jax.make_array_from_process_local_data (pipeline.py _put_global).
    cfg = Config(action="train", data_path="/tmp/nodata",
                 rsl_path=os.path.join(a.rsl, f"rank{a.pid}"),
                 dataset="synthetic", model_name="cnn", batch_size=4,
                 nb_epochs=1, debug=True, half_precision=False)
    result = run_train(cfg)
    for i, leaf in enumerate(local_copy(result["state"].params)):
        out[f"resident_p{i}"] = leaf
    history = {"resident": result["history"]}

    # Streaming path: per-batch make_array_from_process_local_data
    # (pipeline.py ShardedLoader._to_device).
    cfg_s = cfg.replace(model_name="mlp", data_mode="stream",
                        rsl_path=os.path.join(a.rsl, f"rank{a.pid}_s"))
    result_s = run_train(cfg_s)
    for i, leaf in enumerate(local_copy(result_s["state"].params)):
        out[f"stream_p{i}"] = leaf
    history["stream"] = result_s["history"]

    # Model-parallel path: params/opt-state sharded over the 'model' axis
    # ACROSS hosts — the end-of-epoch checkpoint save must all-gather
    # collectively on every process (checkpoint.gather_replicated) before
    # main writes; a main-only dispatch would deadlock here.
    if (a.nproc * a.devices_per_proc) % 2 == 0:
        from distributedpytorch_tpu import checkpoint as ckpt

        cfg_mp = cfg.replace(model_name="mlp", model_parallel=2,
                             rsl_path=os.path.join(a.rsl, f"rank{a.pid}_mp"))
        result_mp = run_train(cfg_mp)
        gathered = ckpt.gather_replicated(result_mp["state"])
        for i, leaf in enumerate(local_copy(gathered.params)):
            out[f"mp_p{i}"] = leaf
        history["mp"] = result_mp["history"]

    np.savez(a.out, **out)
    with open(a.out + ".history.json", "w") as f:
        json.dump(history, f)
    print(f"rank {a.pid} done", file=sys.stderr)


if __name__ == "__main__":
    main()
