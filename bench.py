#!/usr/bin/env python3
"""Benchmark: MNIST-shape CNN training throughput, samples/sec/chip.

North-star metric (BASELINE.json / BASELINE.md): MNIST samples/sec/chip on
the flagship CNN through the full training pipeline — host shard gather,
H2D transfer, on-device augmentation, forward/backward, gradient
all-reduce, optimizer update.  Steady-state only: compile and warmup steps
are excluded (BASELINE.md measurement rules), seed 1234, batch 64/replica
(ref config.py:40,44).

``vs_baseline``: the reference publishes no numbers (SURVEY §6), so the
baseline is measured here: the reference's training loop re-created in
torch (same CNN topology, same batch/optimizer/loss, host augmentation like
ref dataloader.py's transform pipeline) on this host's CPU — the only
hardware the reference can use in this environment (its CUDA path needs
NVIDIA GPUs; TPUs are unsupported by it).  vs_baseline =
ours_samples_per_sec_per_chip / reference_samples_per_sec.

Prints exactly one JSON line to stdout; diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_ours(batch_per_replica: int, steps: int, warmup: int,
               model_name: str) -> dict:
    import jax

    from distributedpytorch_tpu import runtime, utils
    from distributedpytorch_tpu.data.datasets import load_dataset
    from distributedpytorch_tpu.data.pipeline import ResidentLoader
    from distributedpytorch_tpu.models import get_model, get_model_input_size
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    mesh = runtime.make_mesh()
    n_chips = runtime.world_size()
    log(f"devices: {n_chips} x {jax.devices()[0].device_kind}")

    dataset = load_dataset("synthetic", "/tmp/bench_data", seed=1234)
    # Device-resident mode (the framework's default for HBM-sized corpora):
    # one XLA dispatch per epoch-chunk, zero per-step host involvement.
    loader = ResidentLoader(dataset.splits["train"], mesh, batch_per_replica,
                            shuffle=True, seed=1234)
    model = get_model(model_name, dataset.nb_classes, half_precision=True)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, len(loader), False)
    engine = Engine(model, model_name, get_loss_fn("cross_entropy"), tx,
                    dataset.mean, dataset.std,
                    get_model_input_size(model_name), half_precision=True)
    state = jax.device_put(
        engine.init_state(utils.root_key(1234), dataset.channels),
        runtime.replicated_sharding(mesh))

    key = utils.root_key(1234)
    global_batch = loader.global_batch

    if steps <= 0:
        # Default: 3 full training epochs fused into ONE XLA dispatch.
        # The resident design allows stacking epoch plans along the scan
        # axis, so dispatch latency (large over this environment's TPU
        # tunnel, small-but-nonzero on local hardware) amortizes away.
        import numpy as _np

        plans = [loader.epoch_plan(e) for e in range(3)]
        idx = jax.device_put(
            _np.concatenate([jax.device_get(p[0]) for p in plans]),
            loader.plan_sharding)
        valid = jax.device_put(
            _np.concatenate([jax.device_get(p[1]) for p in plans]),
            loader.plan_sharding)
    else:
        idx, valid = loader.epoch_plan(0)
        idx, valid = idx[:steps], valid[:steps]
    n_steps = idx.shape[0]

    def run(i, v):
        nonlocal state
        state, metrics = engine.train_epoch(state, loader.images,
                                            loader.labels, i, v, key)
        jax.block_until_ready(metrics["loss"])
        return time.monotonic()

    log(f"warmup: {warmup} steps (includes XLA compile)")
    t0 = time.monotonic()
    run(idx[:warmup], valid[:warmup])
    run(idx, valid)  # compile the measured shape
    log(f"warmup done in {time.monotonic() - t0:.1f}s")

    t0 = time.monotonic()
    t1 = run(idx, valid)
    elapsed = t1 - t0
    sps = n_steps * global_batch / elapsed
    log(f"steady state: {n_steps} steps x {global_batch} global batch "
        f"in {elapsed:.3f}s -> {sps:,.0f} samples/s "
        f"({sps / n_chips:,.0f}/chip)")
    return {"samples_per_sec": sps, "samples_per_sec_per_chip": sps / n_chips,
            "n_chips": n_chips, "global_batch": global_batch,
            "steps": n_steps, "elapsed_s": elapsed}


def bench_reference_torch(batch: int, steps: int, warmup: int) -> float:
    """The reference's training loop (ref classif.py:28-71) on torch CPU:
    same CNN topology, Adam(1e-3), CE loss, host-side augmentation
    mirroring ref dataloader.py:101-108 (rotation + random-resized-crop +
    3-channel repeat + normalize).  Returns samples/sec."""
    try:
        import torch
        import torch.nn as nn
        import torch.nn.functional as F
    except ImportError:
        return float("nan")

    torch.manual_seed(1234)
    torch.set_num_threads(1)

    class SmallCNNTorch(nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(3, 32, 3, padding=1)
            self.c2 = nn.Conv2d(32, 32, 3, padding=1)
            self.c3 = nn.Conv2d(32, 64, 3, padding=1)
            self.c4 = nn.Conv2d(64, 64, 3, padding=1)
            self.fc1 = nn.Linear(64 * 7 * 7, 256)
            self.head = nn.Linear(256, 10)

        def forward(self, x):
            x = F.relu(self.c2(F.relu(self.c1(x))))
            x = F.max_pool2d(x, 2)
            x = F.relu(self.c4(F.relu(self.c3(x))))
            x = F.max_pool2d(x, 2)
            x = x.flatten(1)
            return self.head(F.relu(self.fc1(x)))

    model = SmallCNNTorch()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    criterion = nn.CrossEntropyLoss()
    rng = np.random.default_rng(1234)
    raw = rng.integers(0, 256, size=(steps + warmup, batch, 28, 28),
                       dtype=np.uint8)
    labels = torch.from_numpy(
        rng.integers(0, 10, size=(steps + warmup, batch)).astype(np.int64))

    def augment_host(imgs_u8: np.ndarray) -> torch.Tensor:
        # ref transform pipeline: rotation+crop approximated by a shifted
        # crop + resize (cheaper than the reference's PIL ops — biases the
        # baseline *faster*, i.e. conservatively against us)
        n = imgs_u8.shape[0]
        out = np.empty((n, 28, 28), dtype=np.float32)
        for i in range(n):
            top, left = rng.integers(0, 5, size=2)
            h = rng.integers(20, 28 - max(top, left) + 1)
            crop = imgs_u8[i, top:top + h, left:left + h].astype(np.float32)
            t = torch.from_numpy(crop)[None, None]
            out[i] = torch.nn.functional.interpolate(
                t, size=(28, 28), mode="bilinear", align_corners=False
            )[0, 0].numpy()
        x = torch.from_numpy(out / 255.0)
        x = x[:, None].repeat(1, 3, 1, 1)
        return (x - 0.45) / 0.18

    def step(i: int) -> None:
        x = augment_host(raw[i])
        opt.zero_grad()
        loss = criterion(model(x), labels[i])
        loss.backward()
        opt.step()

    for i in range(warmup):
        step(i)
    t0 = time.monotonic()
    for i in range(warmup, warmup + steps):
        step(i)
    elapsed = time.monotonic() - t0
    sps = steps * batch / elapsed
    log(f"reference (torch CPU, faithful loop): {steps} steps x {batch} "
        f"in {elapsed:.3f}s -> {sps:,.0f} samples/s")
    return sps


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="cnn")
    p.add_argument("--batch", type=int, default=64,
                   help="per-replica batch (ref config.py:40)")
    p.add_argument("--steps", type=int, default=0,
                   help="steps per measured dispatch; 0 = 3 full epochs "
                        "fused into one dispatch (default)")
    p.add_argument("--warmup", type=int, default=20)
    p.add_argument("--ref-steps", type=int, default=30)
    p.add_argument("--skip-reference", action="store_true")
    args = p.parse_args()

    ours = bench_ours(args.batch, args.steps, args.warmup, args.model)
    if args.skip_reference:
        ref_sps = float("nan")
    else:
        ref_sps = bench_reference_torch(args.batch, args.ref_steps, 3)

    value = ours["samples_per_sec_per_chip"]
    vs = (value / ref_sps) if np.isfinite(ref_sps) and ref_sps > 0 else None
    print(json.dumps({
        "metric": "mnist_cnn_train_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/s/chip",
        "vs_baseline": round(vs, 2) if vs is not None else None,
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
