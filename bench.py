#!/usr/bin/env python3
"""Benchmark: training throughput (samples/sec/chip) + MFU.

North-star metric (BASELINE.json / BASELINE.md): MNIST samples/sec/chip on
the flagship CNN through the full training pipeline — host shard gather,
H2D transfer, on-device augmentation, forward/backward, gradient
all-reduce, optimizer update.  Steady-state only: compile and warmup steps
are excluded (BASELINE.md measurement rules), seed 1234, batch 64/replica
(ref config.py:40,44).

Default (what the driver runs): ONE JSON line to stdout with the headline
CNN number; diagnostics on stderr.  Extra modes:

  --suite      also measure large-batch CNN, MLP, ViT, and ResNet-18
               (on a CIFAR-shaped corpus); writes BENCH_SUITE.json
  --scaling    weak-scaling mechanism measurement on a virtual CPU mesh
               (1 vs 8 devices, batch 64/replica) — the only scaling
               number available with one physical chip

MFU: FLOPs come from the analytic model count (ops/flops.py: jaxpr walk
over the forward pass, train = 3x forward — the convention every published
MFU number uses); the peak denominator is dtype-aware (ops/flops.py
per-dtype table: bf16 runs divide by the chip's published bf16 rate, f32
runs by the f32 rate) and every row records ``mfu_peak_dtype``.  The TPU
executable's own cost_analysis() undercounts by orders of magnitude
(post-fusion per-partition estimates) and is recorded only as the
``xla_reported_flops_total`` cross-check field.

``vs_baseline``: the reference publishes no numbers (SURVEY §6), so the
baseline is measured here: the reference's training loop re-created in
torch (same CNN topology, same batch/optimizer/loss, host augmentation like
ref dataloader.py's transform pipeline) on this host's CPU — the only
hardware the reference can use in this environment (its CUDA path needs
NVIDIA GPUs; TPUs are unsupported by it).  vs_baseline =
ours_samples_per_sec_per_chip / reference_samples_per_sec.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def peak_flops(device_kind: str, dtype: str = "bf16") -> float | None:
    # Single source of truth for the peak table: ops/flops.py (shared
    # with the telemetry MFU gauge).  Imported lazily — bench.py sets up
    # the platform before importing the framework.  ``dtype`` selects the
    # denominator (honest MFU: a bf16 run divides by the bf16 peak, an
    # f32 run by the f32 peak — ops/flops.py documents the convention).
    from distributedpytorch_tpu.ops.flops import peak_flops as _pf

    return _pf(device_kind, dtype)


def provenance_block(fresh: bool = True, probe_device: bool = True) -> dict:
    """The provenance stamp every bench artifact carries (ISSUE 12):
    `fresh` (measured in THIS process vs replayed), device, wall-clock
    timestamp, and the tree's git sha — so a stale artifact can't
    masquerade as current.  profile_breakdown.py reuses this block
    verbatim; scripts/check_bench.py gates on the `fresh` flag.

    ``probe_device=False`` skips touching the JAX backend — the
    backend-down fallback path must not re-risk the hang it is
    falling back from."""
    block: dict = {"fresh": bool(fresh),
                   "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
                   "git_sha": None, "device_kind": None}
    try:
        r = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10)
        block["git_sha"] = r.stdout.strip() or None
    except Exception:  # no git / not a checkout: provenance degrades
        pass
    if probe_device:
        try:
            import jax

            block["device_kind"] = jax.devices()[0].device_kind
        except Exception:  # provenance is advisory; a dead backend
            pass           # must not fail the headline row
    return block


def _top_ops_roofline(compiled_short, run_short, device_kind,
                      program: str = "train_epoch") -> list:
    """Trace ONE short execution and return roofline top-3 ops.

    Runs strictly AFTER the timed measurement (profiling alters
    dispatch behavior, and the D2H cliff has already been paid by the
    FLOPs accounting).  The HLO text of the short program feeds the
    analytic per-op join directly — no costs.json round trip."""
    import shutil
    import tempfile

    import jax

    from distributedpytorch_tpu import roofline

    td = tempfile.mkdtemp(prefix="bench_roofline_")
    try:
        jax.profiler.start_trace(td)
        try:
            run_short()
        finally:
            jax.profiler.stop_trace()
        costs_data = {"device_kind": device_kind,
                      "programs": {program: {"hlo": compiled_short.as_text()}}}
        rep = roofline.analyze(td, costs_data=costs_data,
                               device_kind=device_kind)
        return roofline.top_ops(rep, 3)
    finally:
        shutil.rmtree(td, ignore_errors=True)


def _force_sync_timing_mode() -> None:
    """Pin the device runtime into its SYNCHRONOUS dispatch mode before
    any timed run (round-4 characterization of this environment's
    tunneled TPU): before the first device->host transfer the runtime
    pipelines dispatches and ``block_until_ready`` can return while work
    is still in flight (a timed call then measures ~0); after the first
    D2H every dispatch is synchronous and timings are truthful, at the
    cost of a FIXED ~146 ms per dispatch (amortized here by fusing 12
    epochs per dispatch).  One tiny transfer makes the mode — and the
    numbers — deterministic.  On local hardware this is a no-op."""
    import jax
    import jax.numpy as jnp

    jax.device_get(jnp.zeros(()))


def _make_corpus(image_size: int, channels: int, num_train: int):
    """Synthetic corpus of the requested shape (28x28x1 MNIST-shaped or
    32x32x3 CIFAR-shaped), via the framework's deterministic generator."""
    from distributedpytorch_tpu.data.datasets import Dataset, Split
    from distributedpytorch_tpu.data.io import make_synthetic

    tr_x, tr_y, te_x, te_y = make_synthetic(
        num_train=num_train, num_test=8, image_size=image_size,
        channels=channels, seed=1234)
    mean = float(tr_x.astype(np.float32).mean() / 255.0)
    std = float(tr_x.astype(np.float32).std() / 255.0)
    return Dataset("synthetic", {"train": Split(tr_x, tr_y),
                                 "test": Split(te_x, te_y)}, mean, std)


def bench_ours(batch_per_replica: int, steps: int, model_name: str,
               image_size: int = 28, channels: int = 1,
               num_train: int = 60000, epochs_fused: int = 12,
               half_precision: bool = True, moe_experts: int = 0,
               pallas_dw: bool = False, precision: str | None = None,
               remat: str = "none", scan_layers: bool = False) -> dict:
    import jax

    from distributedpytorch_tpu import runtime, utils
    from distributedpytorch_tpu.data.pipeline import ResidentLoader
    from distributedpytorch_tpu.models import get_model, get_model_input_size
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.precision import from_flags
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    mesh = runtime.make_mesh()
    n_chips = runtime.world_size()
    device_kind = jax.devices()[0].device_kind
    policy = from_flags(precision, half_precision)
    log(f"devices: {n_chips} x {device_kind} | model {model_name} "
        f"batch {batch_per_replica}/replica corpus "
        f"{image_size}x{image_size}x{channels} precision {policy.name}"
        + (f" remat {remat}" if remat != "none" else ""))

    dataset = _make_corpus(image_size, channels, num_train)
    # Device-resident mode (the framework's default for HBM-sized corpora):
    # one XLA dispatch per epoch-chunk, zero per-step host involvement.
    loader = ResidentLoader(dataset.splits["train"], mesh, batch_per_replica,
                            shuffle=True, seed=1234)
    model = get_model(model_name, dataset.nb_classes,
                      precision=policy, remat=remat,
                      moe_experts=moe_experts, mesh=mesh,
                      pallas_dw=pallas_dw, scan_layers=scan_layers)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, len(loader), False)
    engine = Engine(model, model_name, get_loss_fn("cross_entropy"), tx,
                    dataset.mean, dataset.std,
                    get_model_input_size(model_name),
                    precision=policy, remat=remat)
    state = jax.device_put(
        engine.init_state(utils.root_key(1234)),
        runtime.replicated_sharding(mesh))

    key = utils.root_key(1234)
    global_batch = loader.global_batch

    if steps <= 0:
        # Default: `epochs_fused` full training epochs fused into ONE XLA
        # dispatch.  The resident design allows stacking epoch plans along
        # the scan axis, so dispatch latency (large over this environment's
        # TPU tunnel, small-but-nonzero on local hardware) amortizes away.
        # Plans are concatenated ON DEVICE: measured round 4, the FIRST
        # device->host transfer in this process permanently switches the
        # tunnel into a mode where every subsequent dispatch pays a FIXED
        # ~146 ms (characterized: cost is per-call, not per-step, and
        # never recovers) — so the whole prep path below must stay free
        # of jax.device_get until the timed runs are done (the FLOPs
        # accounting that needs host values runs afterwards).
        idx_k, valid_k = loader.epoch_plan_many(range(epochs_fused))
        idx = idx_k.reshape(-1, idx_k.shape[-1])
        valid = valid_k.reshape(-1, valid_k.shape[-1])
    else:
        idx, valid = loader.epoch_plan(0)
        idx, valid = idx[:steps], valid[:steps]
    n_steps = idx.shape[0]

    # AOT-compile the measured program once and reuse the executable for
    # the timed runs.
    log("compiling measured program (first TPU compile can take ~20-40s)")
    t0 = time.monotonic()
    compiled = engine.train_epoch.lower(
        state, loader.images, loader.labels, idx, valid, key).compile()
    compile_warmup_s = time.monotonic() - t0
    log(f"compiled in {compile_warmup_s:.1f}s")
    # Program size next to the compile time it drives (--scan-layers
    # exists to shrink both; scan-vs-noscan suite rows difference them).
    from distributedpytorch_tpu.costs import hlo_instruction_count

    try:
        hlo_instructions = hlo_instruction_count(compiled.as_text())
    except Exception:  # HLO text is advisory, backend-dependent
        hlo_instructions = None
    _force_sync_timing_mode()

    def run():
        nonlocal state
        state, metrics = compiled(state, loader.images, loader.labels,
                                  idx, valid, key)
        jax.block_until_ready(metrics["loss"])
        return time.monotonic()

    run()  # warmup execution of the measured shape
    t0 = time.monotonic()
    t1 = run()
    elapsed = t1 - t0
    sps = n_steps * global_batch / elapsed

    # Model FLOPs for MFU: the analytic jaxpr count (ops/flops.py) — the
    # TPU executable's cost_analysis() undercounts by orders of magnitude
    # (post-fusion per-partition estimates), so it is recorded only as a
    # cross-check field, never used for MFU.  Runs AFTER the timed loop:
    # it device_gets params, and the first D2H degrades later dispatches
    # (see the plan-concatenation note above).
    from distributedpytorch_tpu.ops import flops as flops_mod

    host_params = jax.device_get(state.params)
    host_bs = jax.device_get(state.batch_stats)
    flops_per_sample = flops_mod.train_flops_per_sample(
        engine.model, host_params, host_bs, batch=global_batch,
        input_size=engine.input_size)
    flops_total = flops_per_sample * global_batch * n_steps
    xla_flops = None
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        xla_flops = float(cost.get("flops", 0.0))
    except Exception:  # cost_analysis is best-effort, backend-dependent
        pass
    out = {"model": model_name, "batch_per_replica": batch_per_replica,
           "image_size": image_size, "channels": channels,
           "samples_per_sec": sps, "samples_per_sec_per_chip": sps / n_chips,
           "n_chips": n_chips, "global_batch": global_batch,
           "steps": n_steps, "elapsed_s": elapsed,
           "compile_warmup_s": round(compile_warmup_s, 3),
           "hlo_instructions": hlo_instructions,
           "scan_layers": scan_layers,
           "device_kind": device_kind, "mfu": None}
    # Honest MFU: the denominator matches the run's compute dtype
    # (ops/flops.py per-dtype peak table), and the row records WHICH
    # peak it divided by so the number is auditable.
    peak_dtype = flops_mod.dtype_label(engine.compute_dtype)
    peak = peak_flops(device_kind, peak_dtype)
    out["precision"] = policy.name
    out["remat"] = remat
    out["mfu_peak_dtype"] = peak_dtype
    out["mfu_peak_flops_per_chip"] = peak
    out["flops_per_sample"] = flops_per_sample
    out["flops_per_step"] = flops_total / n_steps
    out["xla_reported_flops_total"] = xla_flops
    achieved = flops_total / elapsed
    out["achieved_tflops"] = achieved / 1e12 / n_chips
    if peak is not None:
        out["mfu"] = achieved / (peak * n_chips)
    # Top-3 ops by self time with their bound class (ISSUE 12): the
    # explanation layer for BENCH deltas.  A separate SHORT plan is
    # compiled and traced once — tracing the 12-epoch fused dispatch
    # would produce a gigabyte trace for the same ranking.  Advisory:
    # any failure leaves the row without top_ops, never without a
    # measurement.
    try:
        k = min(8, n_steps)
        sidx, svalid = idx[:k], valid[:k]
        compiled_short = engine.train_epoch.lower(
            state, loader.images, loader.labels, sidx, svalid,
            key).compile()

        def run_short():
            _state, metrics = compiled_short(
                state, loader.images, loader.labels, sidx, svalid, key)
            jax.block_until_ready(metrics["loss"])

        out["top_ops"] = _top_ops_roofline(compiled_short, run_short,
                                           device_kind)
        log("top ops by self time: " + ", ".join(
            f"{t['name']} {t['time_share'] * 100:.0f}% ({t['bound']})"
            for t in out["top_ops"]))
    except Exception as e:  # advisory enrichment: a profiler or HLO
        # parse failure must never fail the timed bench itself
        log(f"top-ops roofline skipped ({e})")
    log(f"steady state: {n_steps} steps x {global_batch} global batch "
        f"in {elapsed:.3f}s -> {sps:,.0f} samples/s "
        f"({sps / n_chips:,.0f}/chip)"
        + (f", {out['achieved_tflops']:.1f} TF/s/chip"
           if "achieved_tflops" in out else "")
        + (f", MFU {out['mfu'] * 100:.1f}%" if out["mfu"] else ""))
    return out


def bench_ours_streaming(batch_per_replica: int, model_name: str = "cnn",
                         epochs: int = 2,
                         producer_threads: int = 0) -> dict:
    """The STREAMING data path (ShardedLoader: host index-gather +
    prefetched async device_put per step, engine.train_step dispatch per
    step) on the same corpus as the resident headline — quantifying the
    host-loop cost the resident design avoids (BENCH_SUITE row
    cnn_b64_stream vs cnn_b64).  ``producer_threads > 0`` measures the
    threaded host pipeline (--producer-threads): the same loop with the
    gather + device_put dispatch overlapped behind compute (row
    cnn_b64_stream_threaded)."""
    import jax

    from distributedpytorch_tpu import runtime, utils
    from distributedpytorch_tpu.data.pipeline import ShardedLoader
    from distributedpytorch_tpu.models import get_model, get_model_input_size
    from distributedpytorch_tpu.ops.losses import get_loss_fn
    from distributedpytorch_tpu.train.engine import Engine, make_optimizer

    mesh = runtime.make_mesh()
    n_chips = runtime.world_size()
    dataset = _make_corpus(28, 1, 60000)
    loader = ShardedLoader(dataset.splits["train"], mesh, batch_per_replica,
                           shuffle=True, seed=1234, prefetch=2,
                           producer_threads=producer_threads)
    model = get_model(model_name, dataset.nb_classes)
    tx = make_optimizer("adam", 1e-3, 0.9, 0.1, len(loader), False)
    engine = Engine(model, model_name, get_loss_fn("cross_entropy"), tx,
                    dataset.mean, dataset.std,
                    get_model_input_size(model_name))
    state = jax.device_put(
        engine.init_state(utils.root_key(1234)),
        runtime.replicated_sharding(mesh))
    key = utils.root_key(1234)

    # Per-step host-loop intervals stream into the telemetry sketch
    # (fixed memory, every step covered — not a first-N sample), so the
    # row reports tail latency next to the mean-derived throughput: a
    # straggly p99 with a healthy p50 is the queue hiccuping, which a
    # samples/sec average hides completely.
    from distributedpytorch_tpu.telemetry import Histogram

    step_hist = Histogram("bench/step_host_s")

    def one_epoch(epoch: int, hist=None) -> float:
        nonlocal state
        last = None
        prev = time.perf_counter()
        for images, labels, valid in loader.epoch(epoch):
            state, metrics = engine.train_step(state, images, labels,
                                               valid, key)
            last = metrics["loss"]
            if hist is not None:
                now = time.perf_counter()
                hist.observe(now - prev)
                prev = now
        jax.block_until_ready(last)
        return time.monotonic()

    one_epoch(0)  # compile + warmup epoch
    t0 = time.monotonic()
    for e in range(1, 1 + epochs):
        t1 = one_epoch(e, hist=step_hist)
    elapsed = t1 - t0
    samples = epochs * len(loader) * loader.global_batch
    sps = samples / elapsed

    # Decomposition (VERDICT r5 item 6): where a streaming step's time
    # goes, measured separately under the same forced-sync mode —
    #   host_gather: the numpy fancy-index gather (_host_batches), the
    #     only per-step host compute;
    #   h2d_put:     device_put of one gathered batch, blocked;
    #   dispatch:    one engine.train_step on already-resident inputs —
    #     on this tunneled runtime ~all of it is the fixed per-dispatch
    #     sync cost (the resident rows' per-step time bounds the actual
    #     on-chip compute).
    # The prefetch queue (depth 2) overlaps h2d behind compute; the
    # structural overlap assertion lives in tests/test_resident.py.
    n_host = 0
    t0 = time.monotonic()
    for _arrays in loader._host_batches(97):
        n_host += 1
    t_host = (time.monotonic() - t0) / n_host
    arrays = next(iter(loader._host_batches(98)))

    def put_once():
        jax.block_until_ready(loader._to_device(arrays))

    put_once()
    t0 = time.monotonic()
    for _ in range(20):
        put_once()
    t_put = (time.monotonic() - t0) / 20
    imgs_d, labels_d, valid_d = loader._to_device(arrays)
    st, m = engine.train_step(state, imgs_d, labels_d, valid_d, key)
    jax.block_until_ready(m["loss"])
    t0 = time.monotonic()
    for _ in range(20):
        st, m = engine.train_step(st, imgs_d, labels_d, valid_d, key)
        jax.block_until_ready(m["loss"])
    t_disp = (time.monotonic() - t0) / 20

    hs = step_hist.summary()
    out = {"model": model_name, "batch_per_replica": batch_per_replica,
           "mode": "streaming", "producer_threads": producer_threads,
           "samples_per_sec": sps,
           "samples_per_sec_per_chip": sps / n_chips, "n_chips": n_chips,
           "step_host_ms": {q: round(hs[q] * 1e3, 3)
                            for q in ("p50", "p95", "p99") if q in hs},
           "steps": epochs * len(loader), "elapsed_s": elapsed,
           "device_kind": jax.devices()[0].device_kind,
           "decomposition_ms_per_step": {
               "host_gather": round(t_host * 1e3, 3),
               "h2d_put": round(t_put * 1e3, 3),
               "dispatch_sync_mode": round(t_disp * 1e3, 3),
           }}
    log(f"streaming: {out['steps']} steps x {loader.global_batch} in "
        f"{elapsed:.3f}s -> {sps:,.0f} samples/s | per-step: host "
        f"{t_host * 1e3:.2f} ms, h2d {t_put * 1e3:.2f} ms, dispatch "
        f"{t_disp * 1e3:.2f} ms")
    return out


def bench_reference_torch(batch: int, steps: int, warmup: int) -> float:
    """The reference's training loop (ref classif.py:28-71) on torch CPU:
    same CNN topology, Adam(1e-3), CE loss, host-side augmentation
    mirroring ref dataloader.py:101-108 (rotation + random-resized-crop +
    3-channel repeat + normalize).  Returns samples/sec."""
    try:
        import torch
        import torch.nn as nn
        import torch.nn.functional as F
    except ImportError:
        return float("nan")

    torch.manual_seed(1234)
    torch.set_num_threads(1)

    class SmallCNNTorch(nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(3, 32, 3, padding=1)
            self.c2 = nn.Conv2d(32, 32, 3, padding=1)
            self.c3 = nn.Conv2d(32, 64, 3, padding=1)
            self.c4 = nn.Conv2d(64, 64, 3, padding=1)
            self.fc1 = nn.Linear(64 * 7 * 7, 256)
            self.head = nn.Linear(256, 10)

        def forward(self, x):
            x = F.relu(self.c2(F.relu(self.c1(x))))
            x = F.max_pool2d(x, 2)
            x = F.relu(self.c4(F.relu(self.c3(x))))
            x = F.max_pool2d(x, 2)
            x = x.flatten(1)
            return self.head(F.relu(self.fc1(x)))

    model = SmallCNNTorch()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    criterion = nn.CrossEntropyLoss()
    rng = np.random.default_rng(1234)
    raw = rng.integers(0, 256, size=(steps + warmup, batch, 28, 28),
                       dtype=np.uint8)
    labels = torch.from_numpy(
        rng.integers(0, 10, size=(steps + warmup, batch)).astype(np.int64))

    def augment_host(imgs_u8: np.ndarray) -> torch.Tensor:
        # ref transform pipeline: rotation+crop approximated by a shifted
        # crop + resize (cheaper than the reference's PIL ops — biases the
        # baseline *faster*, i.e. conservatively against us)
        n = imgs_u8.shape[0]
        out = np.empty((n, 28, 28), dtype=np.float32)
        for i in range(n):
            top, left = rng.integers(0, 5, size=2)
            h = rng.integers(20, 28 - max(top, left) + 1)
            crop = imgs_u8[i, top:top + h, left:left + h].astype(np.float32)
            t = torch.from_numpy(crop)[None, None]
            out[i] = torch.nn.functional.interpolate(
                t, size=(28, 28), mode="bilinear", align_corners=False
            )[0, 0].numpy()
        x = torch.from_numpy(out / 255.0)
        x = x[:, None].repeat(1, 3, 1, 1)
        return (x - 0.45) / 0.18

    def step(i: int) -> None:
        x = augment_host(raw[i])
        opt.zero_grad()
        loss = criterion(model(x), labels[i])
        loss.backward()
        opt.step()

    for i in range(warmup):
        step(i)
    t0 = time.monotonic()
    for i in range(warmup, warmup + steps):
        step(i)
    elapsed = time.monotonic() - t0
    sps = steps * batch / elapsed
    log(f"reference (torch CPU, faithful loop): {steps} steps x {batch} "
        f"in {elapsed:.3f}s -> {sps:,.0f} samples/s")
    return sps


def run_suite(args) -> dict:
    """Beyond the headline: large-batch CNN, MLP, ResNet-18 on a
    CIFAR-shaped corpus (BASELINE.md configs 3 and 5)."""
    rows = {}
    rows["cnn_b64"] = bench_ours(64, args.steps, "cnn")
    # same corpus/model through the streaming loader: the host-loop cost
    # the resident design avoids, measured (VERDICT r2 item #7)
    rows["cnn_b64_stream"] = bench_ours_streaming(64, "cnn")
    # the threaded host pipeline (--producer-threads 1): gather +
    # device_put dispatch overlapped behind compute — the PR-2 overlap
    # win on the same loop, measured against the row above
    rows["cnn_b64_stream_threaded"] = bench_ours_streaming(
        64, "cnn", producer_threads=1)
    rows["cnn_b512"] = bench_ours(512, args.steps, "cnn")
    rows["mlp_b64"] = bench_ours(64, args.steps, "mlp")
    # the attention model family (framework addition; models/vit.py)
    rows["vit_b64"] = bench_ours(64, args.steps, "vit")
    # ResNet-18, CIFAR-shaped 32x32x3 corpus, warped to the registry's
    # 224 input on device (the reference resizes everything to 224 too,
    # ref utils.py:24-36).  One epoch per dispatch: at ~1e9 FLOPs/sample
    # the dispatch latency is already amortized.
    rows["resnet_cifar_b64"] = bench_ours(
        64, args.steps, "resnet", image_size=32, channels=3,
        num_train=50000, epochs_fused=1)
    # Expert parallelism: the switch-MoE vit (models/moe.py).  On one
    # chip the experts are replicated (no 'model' axis) — the row
    # measures the dispatch/combine einsum cost of the MoE layers
    # themselves, the part that stays per-device under EP.
    rows["vit_moe4_b64"] = bench_ours(64, args.steps, "vit",
                                      moe_experts=4)
    # The REST of the reference zoo (ref utils.py:38-105) at its
    # registry resolution (224 / inception 299), CIFAR-shaped corpus
    # warped on device like the resnet row.  Corpus sizes are scaled to
    # each model's FLOPs/sample so every row times a multi-second
    # steady-state epoch per dispatch (one epoch = one dispatch; the
    # ~146 ms sync-mode dispatch cost amortizes to <2%).
    for name, n_train in (("alexnet", 50000), ("vgg", 12800),
                          ("squeezenet", 25600), ("densenet", 12800),
                          ("inception", 12800)):
        rows[f"{name}_cifar_b64"] = bench_ours(
            64, args.steps, name, image_size=32, channels=3,
            num_train=n_train, epochs_fused=1)
    # --scan-layers A/B on the deep-zoo extremes (vit: homogeneous
    # transformer blocks; densenet: 58 stacked dense layers — the
    # compile-time worst case).  The interesting columns are
    # compile_warmup_s and hlo_instructions vs the unrolled row above;
    # steady-state throughput should hold (scan trades nothing at
    # runtime) — bench_trend.py differences the pairs.
    rows["vit_b64_scan"] = bench_ours(64, args.steps, "vit",
                                      scan_layers=True)
    rows["densenet_cifar_b64_scan"] = bench_ours(
        64, args.steps, "densenet", image_size=32, channels=3,
        num_train=12800, epochs_fused=1, scan_layers=True)
    return rows


def run_attention_suite(args) -> dict:
    """Long-context attention: the Pallas flash kernel
    (ops/flash_attention.py) vs XLA's fused softmax attention, fwd+bwd,
    causal, bf16 — repetitions fused into ONE lax.scan dispatch (the same
    methodology as the headline bench; per-call host timing is unreliable
    over the tunneled chip)."""
    import jax
    import jax.numpy as jnp

    from distributedpytorch_tpu.ops import attention
    from distributedpytorch_tpu.ops.flash_attention import flash_attention

    def measure(fn, shape, n=200):
        # n=200: the sync-mode fixed dispatch cost (~95-146 ms, see
        # _force_sync_timing_mode) is ONE per timed call; at n=30 it
        # added an identical ~5 ms/iter to both variants and compressed
        # the reported speedup toward 1x.  At n=200 the residual is
        # <0.8 ms/iter — small against every row.
        ks = jax.random.split(jax.random.PRNGKey(1), 3)
        q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)
        grad = jax.grad(
            lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2))

        def body(carry, _):
            dq, _dk, _dv = grad(carry, k, v)
            return carry + 1e-6 * dq.astype(carry.dtype), None

        run = jax.jit(lambda q0: jax.lax.scan(body, q0, None, length=n)[0])
        jax.block_until_ready(run(q))
        t0 = time.monotonic()
        jax.block_until_ready(run(q))
        return (time.monotonic() - t0) / n

    rows = {}
    for b, s in ((4, 2048), (4, 4096), (2, 8192)):
        shape = (b, s, 8, 64)
        t_flash = measure(lambda a, x, y: flash_attention(a, x, y,
                                                          causal=True),
                          shape)
        t_xla = measure(lambda a, x, y: attention.full_attention(
            a, x, y, causal=True), shape)
        rows[f"b{b}_s{s}"] = {
            "shape_BSHD": list(shape), "causal": True, "dtype": "bfloat16",
            "pallas_flash_ms": round(t_flash * 1e3, 2),
            "xla_full_ms": round(t_xla * 1e3, 2),
            "speedup": round(t_xla / t_flash, 2),
        }
        log(f"attention b{b} s{s}: flash {t_flash * 1e3:.2f} ms vs "
            f"xla {t_xla * 1e3:.2f} ms (fwd+bwd) -> "
            f"{t_xla / t_flash:.2f}x")

    # Positional-kernel Mosaic smoke + timing (round-4 advisor low):
    # flash_attention_partial — the ring composition's per-shard kernel,
    # whose global-position masking variants otherwise only ever run in
    # interpret mode on the CPU test mesh — compiled on THIS backend,
    # fwd AND bwd (incl. the lse cotangent), value-checked against full
    # attention (one call spanning all keys == the normalized result).
    from distributedpytorch_tpu.ops import flash_attention as fa

    bh, s, d = 8, 2048, 64
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q, k, v = (jax.random.normal(kk, (bh, s, d), jnp.bfloat16)
               for kk in ks)
    pos = jnp.arange(s, dtype=jnp.int32)

    def partial_loss(a, x, y):
        o, lse = fa.flash_attention_partial(a, x, y, pos, pos, True, None)
        return jnp.sum(o ** 2) + 1e-3 * jnp.sum(lse)

    o, _lse = jax.jit(lambda a, x, y: fa.flash_attention_partial(
        a, x, y, pos, pos, True, None))(q, k, v)
    want = attention.full_attention(
        q.reshape(bh, s, 1, d), k.reshape(bh, s, 1, d),
        v.reshape(bh, s, 1, d), causal=True).reshape(bh, s, d)
    err = float(jnp.max(jnp.abs(o - want.astype(jnp.float32))))
    assert err < 3e-2, f"positional kernel != full attention ({err})"
    grads = jax.jit(jax.grad(partial_loss, argnums=(0, 1, 2)))(q, k, v)
    assert all(bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
               for g in grads), "non-finite positional-kernel grads"

    def pos_body(carry, _):
        dq, _dk, _dv = jax.grad(partial_loss, argnums=(0, 1, 2))(
            carry, k, v)
        return carry + 1e-6 * dq.astype(carry.dtype), None

    n = 200
    run = jax.jit(
        lambda q0: jax.lax.scan(pos_body, q0, None, length=n)[0])
    jax.block_until_ready(run(q))
    t0 = time.monotonic()
    jax.block_until_ready(run(q))
    t_pos = (time.monotonic() - t0) / n
    rows["partial_positional_bh8_s2048"] = {
        "shape_BHSD": [bh, s, d], "causal": True, "dtype": "bfloat16",
        "pallas_partial_ms": round(t_pos * 1e3, 2),
        "max_abs_err_vs_full": err,
        "note": "ring per-shard kernel (global-position masking), "
                "fwd+bwd incl. lse cotangent, compiled via Mosaic",
    }
    log(f"attention partial/positional bh{bh} s{s}: {t_pos * 1e3:.2f} ms "
        f"(fwd+bwd), max|err| {err:.2e}")
    return rows


def _run_child(*child_args: str, timeout: float = 3000) -> dict:
    """Run this script as a subprocess with a scrubbed JAX env (the
    child pins its own platform/device count) and parse the JSON it
    prints on its last stdout line.  Shared by the scaling / pipeline /
    ring sections."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), *child_args],
        capture_output=True, text=True, env=env, timeout=timeout)
    if r.returncode != 0:
        log(r.stderr[-2000:])
        raise RuntimeError(f"bench child {child_args} failed")
    return json.loads(r.stdout.strip().splitlines()[-1])


def run_pipeline_bench(args) -> dict:
    """GPipe schedule cost, measured (VERDICT r3 item #3): fwd+bwd of the
    stacked transformer blocks run sequentially vs pipelined at P=4
    stages with M=4 and M=8 microbatches, on the 8-device virtual CPU
    mesh (2 data x 4 model) — the only multi-device host available (PP
    needs >= 2 chips; this environment has one).  All virtual devices
    share one core, so wall time measures TOTAL work: the pipelined
    schedule computes (P+M-1)/M x the sequential FLOPs (idle-tick
    garbage included), i.e. the bubble model predicts 1.75x at M=4 and
    1.375x at M=8 — the measurement validates that model and the
    --pipeline-microbatches lever.  On real chips the P stages run in
    PARALLEL, so per-step wall time is ~(P+M-1)/(P*M) of sequential
    per-stage work + one ppermute per tick; the bubble fraction
    (P-1)/(M+P-1) is what M shrinks."""
    out = _run_child("--pipeline-child", "1")
    for k, v in out.items():
        if isinstance(v, dict) and "ms" in v:
            log(f"pipeline {k}: {v['ms']:.1f} ms/call"
                + (f" ({v['vs_sequential']:.2f}x vs sequential, "
                   f"predicted {v['predicted_work_ratio']:.2f}x)"
                   if "vs_sequential" in v else ""))
    return out


def pipeline_child() -> None:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    from distributedpytorch_tpu import runtime
    from distributedpytorch_tpu.models.vit_pipeline import (
        make_pipeline_fn, sequential_blocks)

    P, DIM, DEPTH, HEADS = 4, 128, 4, 4
    mesh = runtime.make_mesh(model_parallel=P)
    rng = np.random.default_rng(0)
    params = {
        "ln1_scale": jnp.ones((DEPTH, DIM)),
        "ln1_bias": jnp.zeros((DEPTH, DIM)),
        "qkv_kernel": jnp.asarray(
            rng.normal(0, 0.02, (DEPTH, DIM, 3 * DIM)), jnp.float32),
        "qkv_bias": jnp.zeros((DEPTH, 3 * DIM)),
        "proj_kernel": jnp.asarray(
            rng.normal(0, 0.02, (DEPTH, DIM, DIM)), jnp.float32),
        "proj_bias": jnp.zeros((DEPTH, DIM)),
        "ln2_scale": jnp.ones((DEPTH, DIM)),
        "ln2_bias": jnp.zeros((DEPTH, DIM)),
        "up_kernel": jnp.asarray(
            rng.normal(0, 0.02, (DEPTH, DIM, 4 * DIM)), jnp.float32),
        "up_bias": jnp.zeros((DEPTH, 4 * DIM)),
        "down_kernel": jnp.asarray(
            rng.normal(0, 0.02, (DEPTH, 4 * DIM, DIM)), jnp.float32),
        "down_bias": jnp.zeros((DEPTH, DIM)),
    }
    x = jnp.asarray(rng.normal(0, 1, (16, 64, DIM)), jnp.float32)
    w = jnp.asarray(rng.normal(0, 1, x.shape), jnp.float32)

    def timed(fn):
        g = jax.jit(jax.grad(lambda p: jnp.sum(fn(p, x) * w)))
        jax.block_until_ready(g(params))  # compile+warm
        t0 = time.monotonic()
        jax.block_until_ready(g(params))
        return time.monotonic() - t0

    t_seq = timed(lambda p, a: sequential_blocks(p, a, HEADS, DEPTH))
    out = {"config": {"stages": P, "depth": DEPTH, "dim": DIM,
                      "batch": int(x.shape[0]), "seq": int(x.shape[1]),
                      "mesh": "2 data x 4 model, virtual CPU",
                      "note": "single-core host: wall time ~ TOTAL work; "
                              "real chips run stages in parallel"},
           "sequential": {"ms": t_seq * 1e3}}
    for m in (4, 8):
        t = timed(make_pipeline_fn(mesh, P, DEPTH, HEADS, n_micro=m))
        out[f"gpipe_m{m}"] = {
            "ms": t * 1e3, "vs_sequential": t / t_seq,
            "predicted_work_ratio": (P + m - 1) / m,
            "bubble_fraction": (P - 1) / (P + m - 1),
        }
    # The ring x pipeline COMPOSITION on the 3-D (2 data, 2 stage,
    # 2 seq) mesh — same caveat: one core, wall time ~ total work, so
    # the row measures the composition's mechanism overhead (ring
    # rotations inside every stage tick), not TPU speed.  Value is
    # pinned to the plain sequential schedule in tests/test_pipeline.py.
    mesh3 = runtime.make_mesh(model_parallel=2, seq_parallel=2)
    t_rpp = timed(make_pipeline_fn(mesh3, 2, DEPTH, HEADS, ring=True))
    out["ring_pipeline_p2s2"] = {
        "ms": t_rpp * 1e3, "vs_sequential": t_rpp / t_seq,
        # GPipe work ratio for P=2, M=2; the ring's rotation work inside
        # every stage tick comes on top of it
        "predicted_work_ratio": (2 + 2 - 1) / 2,
        "mesh": "2 data x 2 stage x 2 seq",
    }
    print(json.dumps(out), flush=True)


def run_ring_bench(args) -> dict:
    """Long-context ring attention at S=8192 ACROSS the (virtual) mesh:
    the einsum ring vs the ring x flash composition (--attention
    ring_flash), value-checked against unsharded full attention.  Runs on
    the 8-device virtual CPU mesh — with one physical chip the multi-chip
    ring cannot execute on TPU hardware, so the wall times here are
    mechanism/correctness evidence (interpret-mode Pallas on CPU), NOT
    TPU performance; the kernel's on-chip speed is measured separately in
    the attention suite (single-chip flash vs XLA rows)."""
    out = _run_child("--ring-child", "1")
    for k, v in out.items():
        if isinstance(v, dict) and "ms" in v:
            log(f"ring {k}: {v['ms']:.0f} ms (max err vs full "
                f"{v['max_err_vs_full']:.1e})")
    return out


def ring_child() -> None:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    from distributedpytorch_tpu import runtime
    from distributedpytorch_tpu.ops import attention

    B, S, H, D = 1, 8192, 2, 64
    mesh = runtime.make_mesh(data_parallel=1, model_parallel=8)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32)
               for kk in ks)
    want = np.asarray(attention.full_attention(q, k, v, causal=True))
    sh = attention.sequence_sharding(mesh)
    qs, ks_, vs = (jax.device_put(x, sh) for x in (q, k, v))

    out = {"config": {"shape_BSHD": [B, S, H, D], "causal": True,
                      "mesh": "8-way sequence ('model') axis, virtual CPU",
                      "note": "wall times are CPU/interpret mechanism "
                              "evidence, not TPU perf (1 physical chip; "
                              "the multi-chip ring is TPU-gated)"}}
    for name, use_flash in (("einsum_ring", False), ("ring_flash", True)):
        fn = lambda: attention.ring_attention(
            qs, ks_, vs, mesh, causal=True, use_flash=use_flash)
        got = np.asarray(fn())  # compile + correctness
        t0 = time.monotonic()
        jax.block_until_ready(fn())
        out[name] = {"ms": (time.monotonic() - t0) * 1e3,
                     "max_err_vs_full": float(np.abs(got - want).max())}
    print(json.dumps(out), flush=True)


def run_scaling(args) -> dict:
    """Scaling-MECHANISM measurement on the virtual CPU mesh: the same
    global batch (64) run unsharded on 1 device vs sharded over 8, same
    host.  Throughput cannot scale here (this host has one CPU core — all
    virtual devices share it), but the sharded program's partitioning +
    collective overhead IS measurable: overhead = t_step(8)/t_step(1) - 1.
    On real chips that overhead (over ICI) is what stands between this
    design and linear scaling; the sharded==unsharded numerics are proven
    separately in tests/test_distributed.py."""
    out = {}
    for n in (1, 8):
        out[f"cpu{n}"] = _run_child("--scaling-child", str(n),
                                    "--steps", "10")
        ms = (out[f"cpu{n}"]["elapsed_s"] / out[f"cpu{n}"]["steps"]) * 1e3
        log(f"scaling n={n}: {ms:.1f} ms/step (global batch 64)")
    t1 = out["cpu1"]["elapsed_s"] / out["cpu1"]["steps"]
    t8 = out["cpu8"]["elapsed_s"] / out["cpu8"]["steps"]
    out["sharded_step_overhead_1to8"] = t8 / t1 - 1.0
    log(f"sharded-step overhead (8-way vs unsharded, same global batch, "
        f"single-core host): {out['sharded_step_overhead_1to8'] * 100:+.1f}%")
    return out


def scaling_child(n: int, args) -> None:
    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    # Same GLOBAL batch (64) whatever the device count, so 1-device vs
    # 8-device compare sharding overhead, not different workloads.
    # float32: bf16 is software-emulated (and uselessly slow) on CPU.
    res = bench_ours(64 // n, args.steps, "cnn", num_train=2048,
                     half_precision=False)
    print(json.dumps(res), flush=True)


def _backend_alive(timeout_s: float = 300.0) -> bool:
    """Probe backend init in a SUBPROCESS with a timeout.

    This environment's tunneled TPU can go UNAVAILABLE for hours
    (observed round 5), and when it does, ``jax.devices()`` HANGS
    rather than erroring — an unguarded bench would then never print
    its JSON line at all.  First compile can legitimately take ~40 s;
    300 s is far past any healthy init.  The probe costs one extra
    backend init (~10-40 s) per healthy run — accepted insurance: the
    alternative is the driver recording NOTHING for the round when the
    tunnel is down (set DPT_SKIP_BACKEND_PROBE=1 to skip)."""
    if os.environ.get("DPT_SKIP_BACKEND_PROBE") == "1":
        return True
    try:
        res = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        log("backend probe HUNG past the timeout (the tunnel-down "
            "signature)")
        return False
    if res.returncode != 0:
        # Not necessarily the tunnel: a broken jax install or bad env
        # also lands here — surface the child's stderr so the real
        # cause is never silently relabeled.
        log("backend probe FAILED (nonzero exit, not a hang) — stderr "
            "tail:\n" + (res.stderr or "")[-2000:])
        return False
    return True


def _fallback_headline() -> dict | None:
    """Last committed on-chip headline (BENCH_SUITE.json cnn_b64), for
    the backend-down path — clearly labeled as stale, never silent."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_SUITE.json")
    try:
        with open(path) as f:
            row = json.load(f)["suite"]["cnn_b64"]
        return {"metric": "mnist_cnn_train_samples_per_sec_per_chip",
                "value": round(row["samples_per_sec_per_chip"], 1),
                "unit": "samples/s/chip",
                # Machine-readable provenance (VERDICT r5 weak #1):
                # consumers gate on this flag, not the error prose.  A
                # replayed measurement must NEVER carry vs_baseline.
                # probe_device=False: this path exists because the
                # backend is down — don't re-risk the hang.
                **provenance_block(fresh=False, probe_device=False),
                "vs_baseline": None,
                "mfu": (round(row["mfu"], 4) if row.get("mfu")
                        else None),
                "mfu_peak_dtype": row.get("mfu_peak_dtype"),
                "error": "TPU backend unavailable at run time "
                         "(tunnel down); value is the last on-chip "
                         "measurement committed in BENCH_SUITE.json "
                         "from this same tree, NOT a fresh run"}
    except Exception:  # unreadable/alien suite file: no replay row
        return None


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="cnn")
    p.add_argument("--batch", type=int, default=64,
                   help="per-replica batch (ref config.py:40)")
    p.add_argument("--steps", type=int, default=0,
                   help="steps per measured dispatch; 0 = 12 full epochs "
                        "fused into one dispatch (default)")
    p.add_argument("--ref-steps", type=int, default=30)
    p.add_argument("--skip-reference", action="store_true")
    p.add_argument("--suite", action="store_true",
                   help="also bench large-batch/mlp/resnet; writes "
                        "BENCH_SUITE.json")
    p.add_argument("--scaling", action="store_true",
                   help="virtual-CPU-mesh 1->8 weak-scaling measurement; "
                        "adds to BENCH_SUITE.json")
    p.add_argument("--scaling-child", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--pipeline-child", type=int, default=0,
                   help=argparse.SUPPRESS)
    p.add_argument("--ring-child", type=int, default=0,
                   help=argparse.SUPPRESS)
    args = p.parse_args()

    if not (args.scaling_child or args.pipeline_child
            or args.ring_child) and not _backend_alive():
        fallback = _fallback_headline()
        log("TPU backend unreachable (init hang/error after 300 s); "
            "emitting the labeled last-known measurement instead of "
            "hanging" if fallback else
            "TPU backend unreachable and no committed BENCH_SUITE.json "
            "to fall back to")
        if fallback is None:
            fallback = {"metric": "mnist_cnn_train_samples_per_sec_per_"
                                  "chip", "value": None,
                        "unit": "samples/s/chip", "fresh": False,
                        "vs_baseline": None, "mfu": None,
                        "error": "TPU backend unavailable at run time"}
        print(json.dumps(fallback), flush=True)
        return 0

    if args.scaling_child:
        scaling_child(args.scaling_child, args)
        return 0
    if args.pipeline_child:
        pipeline_child()
        return 0
    if args.ring_child:
        ring_child()
        return 0

    extra = {}
    if args.suite:
        extra["suite"] = run_suite(args)
        import jax

        if jax.default_backend() == "tpu":
            extra["attention"] = run_attention_suite(args)
        else:
            # off-TPU the Pallas kernels run in interpret mode — emulated
            # S=8192 attention would take hours; the rows are TPU-only
            log("skipping attention suite (no TPU backend; the Pallas "
                "kernels would run in interpret mode)")
        # multi-device sections run in CPU-mesh subprocesses either way
        extra["pipeline"] = run_pipeline_bench(args)
        extra["ring_longcontext"] = run_ring_bench(args)
    if args.scaling:
        extra["scaling"] = run_scaling(args)
    if extra:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "BENCH_SUITE.json")
        merged = {}
        if os.path.exists(path):  # keep rows from earlier partial runs
            try:
                with open(path) as f:
                    merged = json.load(f)
            except Exception:  # corrupt earlier suite: overwrite fresh
                pass
        merged.update(extra)
        with open(path, "w") as f:
            json.dump(merged, f, indent=2)
        log(f"wrote {path}")

    if args.suite:
        # The headline is DEFINED as cnn@batch-64 (ref config.py:40); with
        # --suite that row is reused and --model/--batch only affect a
        # non-suite run, so the reference below must also run at batch 64
        # for vs_baseline to compare like with like.
        ours = extra["suite"]["cnn_b64"]
        ref_batch = 64
    else:
        ours = bench_ours(args.batch, args.steps, args.model)
        ref_batch = args.batch
    if args.skip_reference:
        ref_sps = float("nan")
    else:
        ref_sps = bench_reference_torch(ref_batch, args.ref_steps, 3)

    value = ours["samples_per_sec_per_chip"]
    vs = (value / ref_sps) if np.isfinite(ref_sps) and ref_sps > 0 else None
    print(json.dumps({
        "metric": "mnist_cnn_train_samples_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "samples/s/chip",
        # provenance block (VERDICT r5 weak #1 + ISSUE 12): this row was
        # MEASURED in this process; replayed fallbacks carry fresh=false
        # and a null vs_baseline (scripts/check_bench.py gates on it)
        **provenance_block(fresh=True),
        "vs_baseline": round(vs, 2) if vs is not None else None,
        "mfu": (round(ours["mfu"], 4) if ours.get("mfu") else None),
        "mfu_peak_dtype": ours.get("mfu_peak_dtype"),
        "top_ops": ours.get("top_ops"),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
