#!/usr/bin/env python3
"""Entry point — north-star contract (BASELINE.json):

    python main.py train -d $DATAPATH
    python main.py test  -d $DATAPATH -f $MODELFILE

TPU-native re-design of georand/distributedpytorch's main.py: no IP table,
no process spawn — topology comes from the JAX runtime (see
distributedpytorch_tpu/runtime.py).
"""

import sys

from distributedpytorch_tpu.cli import main

if __name__ == "__main__":
    sys.exit(main())
